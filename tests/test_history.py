"""History plane (obs/history.py): journal-mined flap priors with
exponential decay + sticky-penalty hysteresis, per-rung remediation
success rates and the skip sets they drive, the burn-rate urgency
window, the reconciler's checkpoint ConfigMap (diff-gated, resumable
across shard failover), the bounded ``status.history`` rollup's
zero-steady-write contract, ``/debug/history``, ``why --forecast``,
and the support bundle's history member."""

import json
import os
import sys
import tarfile
import urllib.error
import urllib.request

import pytest

from tpu_network_operator.agent import report as rpt
from tpu_network_operator.api.v1alpha1 import (
    NetworkClusterPolicy,
    default_policy,
)
from tpu_network_operator.api.v1alpha1.types import API_VERSION
from tpu_network_operator.controller.health import (
    METRIC_HELP,
    HealthServer,
    Metrics,
)
from tpu_network_operator.controller.reconciler import (
    NetworkClusterPolicyReconciler,
)
from tpu_network_operator.kube.fake import FakeCluster
from tpu_network_operator.obs import HistoryEngine, SloEngine, Timeline
from tpu_network_operator.obs import history as hist_mod
from tpu_network_operator.obs import timeline as tl_mod
from tpu_network_operator.remediation import Knobs
from tpu_network_operator.remediation.policy import (
    LADDERS,
    effective_ladder,
)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools",
))
import why as why_mod   # noqa: E402 — tools/ scripts, not a package
import diag as diag_mod   # noqa: E402

NAMESPACE = "tpunet-system"
POLICY = "hist-pol"

pytestmark = pytest.mark.history


def engine(clock, **kw):
    tl = Timeline(clock=lambda: clock[0])
    return tl, HistoryEngine(tl, clock=lambda: clock[0], **kw)


def flap(tl, node, ts=None, heal=False):
    """One probe verdict edge; the Reachable -> Degraded direction is
    the flap the engine scores."""
    tl.record(
        POLICY, tl_mod.KIND_PROBE, node=node,
        frm="Degraded" if heal else "Reachable",
        to="Reachable" if heal else "Degraded", ts=ts,
    )


# -- flap priors: decay scoring + hysteresis -----------------------------------


class TestFlapPriors:
    def test_decay_scoring(self):
        clock = [0.0]
        tl, h = engine(clock)
        flap(tl, "n1", ts=0.0)
        assert h.flap_score(POLICY, "n1", asof=0.0) \
            == pytest.approx(1.0)
        # one half-life halves the mass; two quarter it
        assert h.flap_score(POLICY, "n1", asof=1800.0) \
            == pytest.approx(0.5)
        assert h.flap_score(POLICY, "n1", asof=3600.0) \
            == pytest.approx(0.25)
        flap(tl, "n1", ts=1800.0)
        assert h.flap_score(POLICY, "n1", asof=1800.0) \
            == pytest.approx(1.5)

    def test_latch_asserts_at_threshold(self):
        clock = [0.0]
        tl, h = engine(clock)
        flap(tl, "n1", ts=0.0)
        flap(tl, "n1", ts=0.0)
        assert h.penalized(POLICY) == frozenset()
        flap(tl, "n1", ts=0.0)   # decayed mass 3.0 >= assert
        assert ("n1", "") in h.penalized(POLICY)
        assert h.plan_penalties(POLICY) == {
            "n1": hist_mod.PLAN_PENALTY_RTT_MS,
        }
        assert h.plan_fingerprint(POLICY) == "n1|"

    def test_hysteresis_outlives_heals_then_releases(self):
        clock = [0.0]
        tl, h = engine(clock)
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "n1", ts=ts)
        assert ("n1", "") in h.penalized(POLICY)
        # one half-life later the mass (~1.5) is BELOW assert but
        # above release: the latch holds — a just-healed chronic
        # flapper is not re-trusted on the first quiet pass
        clock[0] = 1800.0
        assert h.flap_score(POLICY, "n1") < h.penalty_assert
        assert ("n1", "") in h.penalized(POLICY)
        # two half-lives on, the mass (~0.75) crosses below release
        # and the latch lets go
        clock[0] = 3600.0
        assert ("n1", "") not in h.penalized(POLICY)
        assert h.plan_fingerprint(POLICY) == ""

    def test_release_bumps_version_for_structural_replan(self):
        clock = [0.0]
        tl, h = engine(clock)
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "n1", ts=ts)
        v_latched = h.priors_version(POLICY)
        clock[0] = 3600.0
        h.penalized(POLICY)   # lazy release happens on read
        assert h.priors_version(POLICY) > v_latched

    def test_telemetry_anomaly_scores_per_interface(self):
        clock = [0.0]
        tl, h = engine(clock)
        tl.record(POLICY, tl_mod.KIND_TELEMETRY, node="n2",
                  frm="nominal", to="anomalous",
                  detail="ens9: error-ratio", ts=5.0)
        assert h.flap_score(POLICY, "n2", iface="ens9", asof=5.0) \
            == pytest.approx(1.0)
        assert h.flap_score(POLICY, "n2", asof=5.0) == 0.0

    def test_departed_node_drops_its_priors(self):
        clock = [0.0]
        tl, h = engine(clock)
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "n1", ts=ts)
        assert ("n1", "") in h.penalized(POLICY)
        tl.record(POLICY, tl_mod.KIND_READINESS, node="n1",
                  frm="not-ready", to="departed", ts=3.0)
        assert h.penalized(POLICY) == frozenset()
        assert h.flap_score(POLICY, "n1") == 0.0

    def test_key_bound_evicts_quietest_not_sticky(self):
        clock = [0.0]
        tl, h = engine(clock)
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "sticky-node", ts=ts)
        for i in range(hist_mod.MAX_KEYS + 8):
            flap(tl, f"noise-{i:04d}", ts=10.0 + i)
        assert ("sticky-node", "") in h.penalized(POLICY)


# -- rung priors ---------------------------------------------------------------


def rem_started(tl, node, cls, action, did, ts=None):
    tl.record(POLICY, tl_mod.KIND_REMEDIATION, node=node,
              frm=cls, to=action, reason="RemediationStarted",
              directive_id=did, ts=ts)


def rem_outcome(tl, node, did, ok, ts=None):
    tl.record(POLICY, tl_mod.KIND_REMEDIATION, node=node,
              frm="pending", to="ok" if ok else "failed",
              reason="RemediationOutcome", directive_id=did, ts=ts)


class TestRungPriors:
    def test_outcomes_mined_by_directive_id(self):
        clock = [0.0]
        tl, h = engine(clock)
        rem_started(tl, "n1", "probe", "re-probe", "d1")
        rem_outcome(tl, "n1", "d1", ok=True)
        rem_started(tl, "n1", "probe", "re-probe", "d2")
        rem_outcome(tl, "n1", "d2", ok=False)
        assert h.rung_stats(POLICY) == {
            ("probe", "re-probe"): (2, 1, 1, 0),
        }

    def test_escalation_counts_against_the_from_rung(self):
        clock = [0.0]
        tl, h = engine(clock)
        tl.record(POLICY, tl_mod.KIND_REMEDIATION, node="n1",
                  frm="re-probe", to="peer-shift",
                  reason="RemediationEscalated", detail="probe")
        assert h.rung_stats(POLICY) == {
            ("probe", "re-probe"): (0, 0, 0, 1),
        }

    def test_skip_needs_min_samples_below_floor(self):
        clock = [0.0]
        tl, h = engine(clock)
        rem_started(tl, "n1", "probe", "re-probe", "d1")
        rem_outcome(tl, "n1", "d1", ok=False)
        rem_started(tl, "n1", "probe", "re-probe", "d2")
        rem_outcome(tl, "n1", "d2", ok=False)
        # 0/2 — below floor but under min samples: no skip yet
        assert h.rung_skips(POLICY) == {}
        rem_started(tl, "n1", "probe", "re-probe", "d3")
        rem_outcome(tl, "n1", "d3", ok=False)
        assert h.rung_skips(POLICY) == {
            "probe": frozenset({"re-probe"}),
        }

    def test_succeeding_rung_never_skipped(self):
        clock = [0.0]
        tl, h = engine(clock)
        for i in range(6):
            did = f"d{i}"
            rem_started(tl, "n1", "probe", "re-probe", did)
            rem_outcome(tl, "n1", did, ok=(i % 2 == 0))   # 50% >> floor
        assert h.rung_skips(POLICY) == {}

    def test_effective_ladder_filters_but_never_empties(self):
        skips = {"probe": frozenset({"re-probe"})}
        assert effective_ladder("probe", Knobs(skip_actions=skips)) \
            == ("peer-shift", "restart-agent")
        # every rung below the floor: the LAST rung survives — a
        # fleet that mined "nothing works" still escalates somewhere
        for cls, ladder in LADDERS.items():
            knobs = Knobs(skip_actions={cls: frozenset(ladder)})
            assert effective_ladder(cls, knobs) == ladder[-1:]


# -- urgency -------------------------------------------------------------------


class _FakeSlo:
    def __init__(self, burn):
        self.burn = burn

    def burn_rate(self, policy, window):
        return self.burn


class TestUrgency:
    def test_burn_shrinks_window_capped(self):
        h = HistoryEngine(slo=_FakeSlo(2.0))
        assert h.budget_window(POLICY, 300.0) == pytest.approx(150.0)
        h.slo = _FakeSlo(100.0)
        assert h.budget_window(POLICY, 300.0) == pytest.approx(
            300.0 / hist_mod.URGENCY_MAX_SCALE
        )

    def test_healthy_burn_keeps_configured_pace(self):
        h = HistoryEngine(slo=_FakeSlo(0.4))
        assert h.budget_window(POLICY, 300.0) == 300.0
        h_none = HistoryEngine()
        assert h_none.budget_window(POLICY, 300.0) == 300.0
        assert h_none.urgency(POLICY) == 0.0


# -- rollup + metrics ----------------------------------------------------------


class TestRollup:
    def test_none_until_anything_folds(self):
        clock = [0.0]
        tl, h = engine(clock)
        assert h.history_status(POLICY) is None

    def test_steady_reads_serve_identical_object(self):
        clock = [0.0]
        tl, h = engine(clock)
        flap(tl, "n1", ts=0.0)
        s1 = h.history_status(POLICY)
        assert s1.tracked_links == 1
        # same fold version + same decay bucket -> the SAME object,
        # so the reconciler's status diff sees no change
        assert h.history_status(POLICY) is s1
        flap(tl, "n1", ts=1.0)
        s2 = h.history_status(POLICY)
        assert s2 is not s1

    def test_rollup_counts_and_gauges(self):
        clock = [0.0]
        m = Metrics()
        tl = Timeline(clock=lambda: clock[0])
        h = HistoryEngine(tl, metrics=m, clock=lambda: clock[0])
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "n1", ts=ts)
        for i in range(3):
            did = f"d{i}"
            rem_started(tl, "n1", "probe", "re-probe", did)
            rem_outcome(tl, "n1", did, ok=False)
        h.budget_window(POLICY, 300.0)
        status = h.history_status(POLICY)
        assert status.tracked_links == 1
        assert status.sticky_penalties == 1
        assert status.flapping_nodes == 1
        assert status.remediation_success_rate == 0.0
        assert status.rungs_skipped == 1
        assert status.budget_window_seconds == 300.0
        rendered = m.render()
        assert "tpunet_history_tracked_links" in rendered
        assert "tpunet_history_sticky_penalties" in rendered
        assert "tpunet_history_rung_success_rate" in rendered
        h.forget(POLICY)
        rendered = m.render()
        for family in hist_mod.HISTORY_GAUGES:
            assert family not in rendered
        assert h.history_status(POLICY) is None

    def test_metric_help_covers_history_families(self):
        for name in hist_mod.HISTORY_GAUGES:
            assert name in METRIC_HELP
        assert "tpunet_fleet_sticky_penalties" in METRIC_HELP


# -- persistence ---------------------------------------------------------------


class TestPayload:
    def _mined(self):
        clock = [0.0]
        tl, h = engine(clock)
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "n1", ts=ts)
        rem_started(tl, "n1", "probe", "re-probe", "d1")
        rem_outcome(tl, "n1", "d1", ok=False)
        return clock, h

    def test_round_trip(self):
        clock, h = self._mined()
        payload = h.to_payload(POLICY)
        assert payload["v"] == hist_mod.PAYLOAD_VERSION
        h2 = HistoryEngine(clock=lambda: clock[0])
        assert h2.load_payload(POLICY, payload)
        assert ("n1", "") in h2.penalized(POLICY)
        assert h2.rung_stats(POLICY) == h.rung_stats(POLICY)
        assert h2.flap_score(POLICY, "n1", asof=0.0) \
            == pytest.approx(h.flap_score(POLICY, "n1", asof=0.0))

    def test_load_is_cold_only(self):
        clock, h = self._mined()
        payload = h.to_payload(POLICY)
        warm = HistoryEngine(clock=lambda: clock[0])
        tl2 = Timeline(clock=lambda: clock[0])
        tl2.add_listener(warm._fold)
        tl2.record(POLICY, tl_mod.KIND_PROBE, node="other",
                   frm="Reachable", to="Degraded", ts=0.0)
        assert not warm.load_payload(POLICY, payload)
        assert warm.flap_score(POLICY, "n1") == 0.0

    def test_mangled_payload_loads_nothing(self):
        h = HistoryEngine()
        assert not h.load_payload(POLICY, None)
        assert not h.load_payload(POLICY, {"v": 999})
        assert not h.load_payload(POLICY, {
            "v": hist_mod.PAYLOAD_VERSION,
            "rungs": {"probe|re-probe": ["NaN-ish", "x"]},
        })
        assert h.priors_version(POLICY) == 0


# -- reconciler integration: checkpoint + failover resume ----------------------


def probe_payload(n, bad=False):
    return {
        "peersTotal": n - 1,
        "peersReachable": 0 if bad else n - 1,
        "unreachable": [],
        "rttP50Ms": 0.4, "rttP99Ms": 1.1,
        "lossRatio": 1.0 if bad else 0.0,
        "state": "Degraded" if bad else "Healthy",
    }


def fleet_report(node, i, n, bad=False):
    return rpt.ProvisioningReport(
        node=node, policy=POLICY, ok=not bad,
        error="link eth1 down" if bad else "",
        backend="tpu", mode="L2",
        interfaces_configured=2, interfaces_total=2,
        probe_endpoint=f"10.7.0.{i + 1}:8477",
        probe=probe_payload(n, bad=bad),
    )


def make_reconciler(fake, clock):
    m = Metrics()
    tl = Timeline(clock=lambda: clock[0], metrics=m)
    slo = SloEngine(tl, metrics=m, clock=lambda: clock[0])
    h = HistoryEngine(tl, metrics=m, slo=slo, clock=lambda: clock[0])
    rec = NetworkClusterPolicyReconciler(
        fake, NAMESPACE, metrics=m, timeline=tl, slo=slo, history=h,
    )
    rec._rem_clock = lambda: clock[0]
    rec.setup()
    return rec, h, tl


def make_env(n=4):
    p = NetworkClusterPolicy()
    p.metadata.name = POLICY
    p.spec.configuration_type = "tpu-so"
    p.spec.node_selector = {"tpunet.dev/pool": POLICY}
    p.spec.tpu_scale_out.probe.enabled = True
    fake = FakeCluster()
    fake.create(default_policy(p).to_dict())
    for i in range(n):
        node = f"node-{i:03d}"
        fake.add_node(node, {"tpunet.dev/pool": POLICY})
        fake.apply(rpt.lease_for(fleet_report(node, i, n), NAMESPACE))
    clock = [10_000.0]
    rec, h, tl = make_reconciler(fake, clock)
    rec.reconcile(POLICY)
    fake.simulate_daemonset_controller()
    rec.reconcile(POLICY)
    return fake, rec, h, tl, clock


def mine_chronic_flapper(fake, rec, clock, node="node-000", flips=4):
    """Flap one node until the sticky latch asserts (each bad/good
    report pair is one Reachable -> Degraded edge)."""
    for _ in range(flips):
        fake.apply(rpt.lease_for(
            fleet_report(node, 0, 4, bad=True), NAMESPACE
        ))
        rec.reconcile(POLICY)
        clock[0] += 5.0
        fake.apply(rpt.lease_for(fleet_report(node, 0, 4), NAMESPACE))
        rec.reconcile(POLICY)
        clock[0] += 5.0


class TestReconcilerHistory:
    def test_status_history_rollup_published(self):
        fake, rec, h, tl, clock = make_env()
        mine_chronic_flapper(fake, rec, clock)
        rec.reconcile(POLICY)
        cr = fake.get(API_VERSION, "NetworkClusterPolicy", POLICY)
        history = cr["status"]["history"]
        assert history["trackedLinks"] == 1
        assert history["stickyPenalties"] == 1
        assert history["flappingNodes"] == 1

    def test_checkpoint_cm_written_and_diff_gated(self):
        fake, rec, h, tl, clock = make_env()
        mine_chronic_flapper(fake, rec, clock)
        rec.reconcile(POLICY)
        cm = fake.get(
            "v1", "ConfigMap", hist_mod.history_cm_name(POLICY),
            NAMESPACE,
        )
        payload = json.loads(cm["data"][hist_mod.HISTORY_CM_KEY])
        assert payload["v"] == hist_mod.PAYLOAD_VERSION
        assert payload["sticky"] == ["node-000|"]
        # the CR owns the checkpoint: policy delete collects it
        owner = cm["metadata"]["ownerReferences"][0]
        assert owner["kind"] == "NetworkClusterPolicy"
        assert owner["name"] == POLICY

    def test_zero_steady_writes_and_appends_with_priors_live(self):
        fake, rec, h, tl, clock = make_env()
        mine_chronic_flapper(fake, rec, clock)
        rec.reconcile(POLICY)
        rec.reconcile(POLICY)   # absorb trailing journal records
        before = {
            k: v for k, v in fake.request_counts.items()
            if k[0] in ("create", "update", "patch", "apply")
        }
        appended = tl.appended()
        for _ in range(5):
            rec.reconcile(POLICY)
        after = {
            k: v for k, v in fake.request_counts.items()
            if k[0] in ("create", "update", "patch", "apply")
        }
        assert before == after
        assert tl.appended() == appended

    def test_failover_successor_does_not_retrust_flapper(self):
        """The ISSUE's resume contract: replica B starts with a COLD
        engine, loads replica A's checkpoint on its first pass, and
        keeps the chronic flapper penalized — no re-learning window
        in which the planner would route back through it."""
        fake, rec_a, h_a, tl_a, clock = make_env()
        mine_chronic_flapper(fake, rec_a, clock)
        rec_a.reconcile(POLICY)
        assert ("node-000", "") in h_a.penalized(POLICY)
        # replica B: fresh process, fresh engine, same cluster
        rec_b, h_b, tl_b = make_reconciler(fake, clock)
        assert h_b.priors_version(POLICY) == 0
        rec_b.reconcile(POLICY)
        assert ("node-000", "") in h_b.penalized(POLICY)
        assert h_b.rung_stats(POLICY) == h_a.rung_stats(POLICY)
        # ... and B's first save diffs against the loaded payload:
        # no rewrite of an unchanged checkpoint
        cm_before = fake.get(
            "v1", "ConfigMap", hist_mod.history_cm_name(POLICY),
            NAMESPACE,
        )
        rec_b.reconcile(POLICY)
        cm_after = fake.get(
            "v1", "ConfigMap", hist_mod.history_cm_name(POLICY),
            NAMESPACE,
        )
        assert cm_before["metadata"].get("resourceVersion") \
            == cm_after["metadata"].get("resourceVersion")

    def test_release_policy_forgets_and_reacquire_reloads(self):
        """Shard handoff: releasing a policy drops the local priors
        (the successor's engine is the authority), and a re-gain
        reloads whatever checkpoint the successor persisted."""
        fake, rec, h, tl, clock = make_env()
        mine_chronic_flapper(fake, rec, clock)
        rec.reconcile(POLICY)
        rec.release_policy(POLICY)
        assert h.priors_version(POLICY) == 0
        assert h.penalized(POLICY) == frozenset()
        rec.reconcile(POLICY)   # re-gained: first pass reloads
        assert ("node-000", "") in h.penalized(POLICY)

    def test_cr_delete_forgets_priors_and_checkpoint_state(self):
        fake, rec, h, tl, clock = make_env()
        mine_chronic_flapper(fake, rec, clock)
        rec.reconcile(POLICY)
        fake.delete(API_VERSION, "NetworkClusterPolicy", POLICY)
        rec.reconcile(POLICY)
        assert h.priors_version(POLICY) == 0
        assert h.history_status(POLICY) is None


# -- shard ownership journal (satellite) ---------------------------------------


class TestShardJournal:
    def _coord(self, fake, ident, clock, tl):
        from tpu_network_operator.controller.sharding import (
            ShardCoordinator,
        )

        return ShardCoordinator(
            fake, NAMESPACE, n_shards=2, identity=ident,
            lease_duration=30.0, clock=lambda: clock[0], timeline=tl,
        )

    def test_acquire_release_failover_edges(self):
        fake = FakeCluster()
        clock = [1000.0]
        tl = Timeline(clock=lambda: clock[0])
        a = self._coord(fake, "replica-a", clock, tl)
        a.sync()
        records = tl.snapshot(policy=tl_mod.SHARD_POLICY,
                              kind=tl_mod.KIND_SHARD)
        assert {(r["to"], r["cause"]["directiveId"])
                for r in records} \
            == {("acquired", "replica-a"), ("acquired", "replica-a")}
        # steady renewals journal nothing
        n0 = tl.appended()
        clock[0] += 10.0
        a.sync()
        assert tl.appended() == n0
        # a crashes (NO clean stop — its leases expire still naming it
        # as holder); b takes the expired leases -> failover edges
        # naming the previous holder as the from-state
        clock[0] += 100.0
        b = self._coord(fake, "replica-b", clock, tl)
        b.sync()
        takeovers = [
            r for r in tl.snapshot(kind=tl_mod.KIND_SHARD)
            if r["cause"]["directiveId"] == "replica-b"
        ]
        assert len(takeovers) == 2
        assert all(r["to"] == "failover" for r in takeovers)
        assert all(r["from"] == "replica-a" for r in takeovers)
        # a clean shutdown journals the release edges
        b.stop()
        released = [
            r for r in tl.snapshot(kind=tl_mod.KIND_SHARD)
            if r["to"] == "released"
        ]
        assert len(released) == 2
        assert all(r["from"] == "replica-b" for r in released)


# -- /debug/history ------------------------------------------------------------


def _get(url, token=""):
    req = urllib.request.Request(
        url,
        headers={"Authorization": f"Bearer {token}"} if token else {},
    )
    with urllib.request.urlopen(req) as resp:
        return resp.status, resp.read().decode()


class TestDebugHistoryEndpoint:
    def _history(self):
        clock = [0.0]
        tl, h = engine(clock)
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "n1", ts=ts)
        return h

    def test_serves_summary(self):
        srv = HealthServer(port=0, history=self._history())
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            status, body = _get(f"{base}/debug/history")
            assert status == 200
            data = json.loads(body)
            assert data["penaltyAssert"] == hist_mod.PENALTY_ASSERT_FLAPS
            link = data["policies"][POLICY]["links"][0]
            assert link["node"] == "n1"
            assert link["sticky"] is True
        finally:
            srv.stop()

    def test_404_without_history(self):
        srv = HealthServer(port=0)
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"http://127.0.0.1:{srv.port}/debug/history")
            assert err.value.code == 404
        finally:
            srv.stop()

    def test_bearer_gate(self):
        srv = HealthServer(port=0, history=self._history(),
                           metrics_auth=lambda tok: tok == "s3cr3t")
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(f"{base}/debug/history")
            assert err.value.code == 403
            status, _ = _get(f"{base}/debug/history", token="s3cr3t")
            assert status == 200
        finally:
            srv.stop()


# -- why --forecast ------------------------------------------------------------


class TestWhyForecast:
    def _engine(self):
        clock = [0.0]
        tl, h = engine(clock)
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "n1", ts=ts)
        for i in range(3):
            did = f"d{i}"
            rem_started(tl, "n1", "probe", "re-probe", did)
            rem_outcome(tl, "n1", did, ok=False)
        return h

    def test_forecast_renders_priors_and_skips(self):
        out = why_mod.forecast("n1", self._engine().summary())
        assert "forecast n1" in out
        assert "STICKY" in out
        assert "re-probe" in out
        assert "success 0.00" in out   # the mined 0/3 rate
        assert "SKIPPED" in out

    def test_forecast_without_evidence(self):
        out = why_mod.forecast("ghost", {"policies": {}})
        assert "no mined priors" in out

    def test_cli_forecast_with_inprocess_engine(self, capsys):
        rc = why_mod.main(
            ["n1", "--forecast", "--policy", POLICY],
            history=self._engine(),
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "forecast n1" in out
        assert "STICKY" in out

    def test_cli_forecast_without_source_errors(self, capsys):
        rc = why_mod.main(["n1", "--forecast"])
        assert rc == 1
        assert "--history-url" in capsys.readouterr().err


# -- support bundle ------------------------------------------------------------


class TestDiagHistory:
    def test_bundle_contains_live_history(self, tmp_path):
        clock = [0.0]
        tl, h = engine(clock)
        for ts in (0.0, 0.0, 0.0):
            flap(tl, "n1", ts=ts)
        out = tmp_path / "bundle.tar.gz"
        members = diag_mod.collect_bundle(
            FakeCluster(), NAMESPACE, str(out), history=h,
        )
        assert "history.json" in members
        with tarfile.open(out) as tar:
            body = json.load(tar.extractfile("history.json"))
            manifest = json.load(tar.extractfile("manifest.json"))
        assert body["policies"][POLICY]["links"][0]["sticky"] is True
        assert "history.json" in manifest["files"]

    def test_bundle_derives_from_status_without_live_engine(
        self, tmp_path
    ):
        fake, rec, h, tl, clock = make_env()
        mine_chronic_flapper(fake, rec, clock)
        rec.reconcile(POLICY)
        out = tmp_path / "bundle.tar.gz"
        members = diag_mod.collect_bundle(fake, NAMESPACE, str(out))
        assert "history.json" in members
        with tarfile.open(out) as tar:
            body = json.load(tar.extractfile("history.json"))
            cm_member = (
                f"configmaps/{hist_mod.history_cm_name(POLICY)}.json"
            )
            cm = json.load(tar.extractfile(cm_member))
        assert body["source"] == "status.history"
        assert body["policies"][POLICY]["stickyPenalties"] == 1
        # the priors checkpoint CM rides in the configmap capture
        assert hist_mod.HISTORY_CM_KEY in cm.get("data", {})

    def test_history_body_redacted(self, tmp_path):
        out = tmp_path / "bundle.tar.gz"
        diag_mod.collect_bundle(
            FakeCluster(), NAMESPACE, str(out),
            history_json=json.dumps({
                "policies": {"p": {
                    "note": "auth failed: Bearer sk-meta-XYZ12345",
                }},
            }),
        )
        with tarfile.open(out) as tar:
            body = tar.extractfile("history.json").read().decode()
        assert "XYZ12345" not in body
        assert "**REDACTED**" in body
