"""Scenario-harness tier: the declarative fleet simulator as tests.

Two layers:

* a smoke of the ``tpu_network_operator.testing`` world itself —
  declarative spec in, converged SLO-judged verdict out, byte-identical
  replay (the contract every scenario in ``tools/simlab`` builds on);
* distilled tier-1 regressions for bugs the scenario suite found, run
  small enough for the fast tier.  The full six-scenario suite runs
  under ``make scenarios`` / ``tools/simlab/run.py``.
"""

import json
import math

import pytest

from tpu_network_operator.kube import chaos
from tpu_network_operator.testing import (
    FaultEvent,
    NodeGroup,
    PolicySpec,
    ScenarioSpec,
    SloBudget,
    World,
    FAULT_DEGRADE,
    FAULT_HEAL,
    FAULT_OUTAGE,
    verdict,
)

pytestmark = pytest.mark.scenario

START = 1_000_000.0


def _pool(name):
    return PolicySpec(name=name, selector={"tpunet.dev/pool": name})


class TestHarnessSmoke:
    def _spec(self, ticks=8):
        t = START
        return ScenarioSpec(
            name="smoke", seed=7, start=t, tick_seconds=15.0,
            ticks=ticks, replicas=2, shards=4,
            groups=[NodeGroup(name="g0", count=8, policy="p0")],
            policies=[_pool("p0")],
            faults=[
                FaultEvent(at=t + 30, kind=FAULT_DEGRADE, group="g0",
                           nodes=2),
                FaultEvent(at=t + 60, kind=FAULT_HEAL, group="g0"),
            ],
            budgets=[SloBudget(policy="p0", fast_max=80.0,
                               require_burn=True)],
            steady_window=3,
        )

    def test_spec_to_verdict(self):
        """Spec in, world out: fleet materialized, faults fire on the
        sim clock, SLO judge passes the recovered run, steady state is
        write-free, two-leaders-never holds across every shard round."""
        with World(self._spec()) as w:
            w.run()
            v = verdict(w)
        assert v["passed"], v
        assert v["statuses"]["p0"]["ready"] == 8
        assert v["invariants"]["zero_steady_writes"] is True
        assert v["budgets"][0]["burn_seen_ok"] is True

    def test_replay_byte_identical(self):
        """Same (spec, seed) twice -> byte-identical verdict JSON.
        This is the property every simlab scenario inherits."""
        outs = []
        for _ in range(2):
            with World(self._spec()) as w:
                w.run()
                outs.append(json.dumps(verdict(w), sort_keys=True))
        assert outs[0] == outs[1]


class TestShardFailoverMidFault:
    """Distilled from simlab scenario (a) shard_storm: PR 11's bench
    only failed over a QUIET fleet; the scenario drives the handoff
    while >= 10% of the departing replica's nodes are mid-fault AND an
    API fault storm is live.  The survivor must take over every shard
    and reconverge."""

    def test_takeover_with_degraded_tenth_under_storm(self):
        spec = ScenarioSpec(
            name="failover-mid-fault", seed=11, start=START,
            tick_seconds=15.0, ticks=12, replicas=2, shards=4,
            lease_duration=30.0,
            groups=[NodeGroup(name=f"g{i}", count=10, policy=f"p{i}")
                    for i in range(2)],
            policies=[_pool(f"p{i}") for i in range(2)],
        )
        with World(spec) as w:
            for verb in ("get", "list", "update"):
                w.inj.schedule_rule(
                    START + 30, chaos.FAULT_503, verb=verb, rate=0.05,
                    duration=90.0,
                )
            w.start()
            w.tick()
            w.tick()
            dying, survivor = w.replicas[0], w.replicas[1]
            mid_fault = 0
            for pname in dying.owned_policies(w.policy_names):
                g = f"g{pname[1:]}"
                want = max(1, math.ceil(0.10 * len(w.members[g])))
                mid_fault += len(w.degrade(g, want))
            departing = sum(
                len(w.members[f"g{p[1:]}"])
                for p in dying.owned_policies(w.policy_names)
            )
            assert departing > 0 and mid_fault / departing >= 0.10
            w.tick()
            dying.stop()
            w.replicas.remove(dying)
            w.now[0] += spec.lease_duration
            for _ in range(4):
                w.tick()
            # every shard moved, never co-owned
            assert set(range(spec.shards)) <= survivor.coord.owned
            assert w.overlap_violations == 0
            for g in list(w.members):
                w.heal_group(g)
            for _ in range(3):
                w.tick()
            from tpu_network_operator.api.v1alpha1.types import (
                API_VERSION,
            )

            for p in w.policy_names:
                st = (
                    w.fake.get(API_VERSION, "NetworkClusterPolicy", p)
                    .get("status", {}) or {}
                )
                assert st.get("state") == "All good", (p, st)
                assert int(st.get("ready", 0)) == 10


class TestOutageStaleCacheRegression:
    """The bug the long_soak scenario found: the informer's watch-
    reopen backoff ran on the WALL clock unconditionally.  Under an
    injected sim clock a reopen that failed during an apiserver outage
    pinned ``_reopen_not_before`` a wall-second ahead — an arbitrary
    stretch of sim time during which sync() silently served the stale
    store as fresh and the control plane missed whole degradation
    waves.  The informer clock is now injectable; this drives the
    exact shape: outage, degrade after it lifts, and the next
    reconcile pass MUST see the degradation."""

    def test_cache_recovers_on_sim_clock_after_outage(self):
        t = START
        spec = ScenarioSpec(
            name="outage-stale-cache", seed=3, start=t,
            tick_seconds=60.0, ticks=10, replicas=1, shards=1,
            groups=[NodeGroup(name="g0", count=6, policy="p0")],
            policies=[_pool("p0")],
            faults=[
                FaultEvent(at=t + 60, kind=FAULT_OUTAGE, duration=90.0),
                # the wave lands AFTER the outage lifts: a wall-clock
                # reopen backoff would still be pinning the cache stale
                FaultEvent(at=t + 240, kind=FAULT_DEGRADE, group="g0",
                           nodes=2),
            ],
        )
        from tpu_network_operator.api.v1alpha1.types import API_VERSION

        with World(spec) as w:
            w.arm_schedule()
            w.start()
            seen_degraded = None
            for tick in range(spec.ticks):
                w.tick()
                st = (
                    w.fake.get(API_VERSION, "NetworkClusterPolicy",
                               "p0").get("status", {}) or {}
                )
                if w.now[0] >= t + 240 and seen_degraded is None:
                    seen_degraded = int(st.get("ready", 0))
            # the FIRST pass after the degrade event already sees it —
            # no wall-clock staleness window
            assert seen_degraded == 4
            # and the SLO engine recorded the dip (the judge's samples
            # were the original failure's missing evidence)
            samples = list(w.slo._samples.get("p0", []))
            assert any(ratio < 1.0 for _ts, ratio in samples), samples
