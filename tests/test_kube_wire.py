"""ApiClient conformance against a real HTTP wire (VERDICT r3 #4).

The reference exercises its client against envtest's real apiserver
(ref internal/controller/suite_test.go:61-102); no apiserver binary
exists here, so kube/wire.py serves the REST API over actual HTTP(S) on
localhost and the real ApiClient talks to it — TLS handshake, chunked
watch decode, reconnect-after-drop, 410 Gone relist, 409 mapping,
server-side apply.  A client-side wire bug now fails these tests instead
of shipping.
"""

import json
import os
import subprocess
import time

import pytest

from tpu_network_operator.kube import errors as kerr
from tpu_network_operator.kube.client import ApiClient, is_openshift
from tpu_network_operator.kube.wire import WireApiServer


def make_policy(name, layer="L2"):
    return {
        "apiVersion": "tpunet.dev/v1alpha1",
        "kind": "NetworkClusterPolicy",
        "metadata": {"name": name},
        "spec": {
            "configurationType": "tpu-so",
            "nodeSelector": {"x": "y"},
            "tpuScaleOut": {"layer": layer},
        },
    }


@pytest.fixture()
def srv():
    with WireApiServer() as s:
        yield s


@pytest.fixture()
def client(srv):
    return ApiClient(srv.url)


class TestCrudOverWire:
    def test_create_get_update_delete(self, client):
        client.create(make_policy("p1"))
        got = client.get("tpunet.dev/v1alpha1", "NetworkClusterPolicy", "p1")
        assert got["spec"]["tpuScaleOut"]["layer"] == "L2"
        got["spec"]["tpuScaleOut"]["layer"] = "L3"
        client.update(got)
        got = client.get("tpunet.dev/v1alpha1", "NetworkClusterPolicy", "p1")
        assert got["spec"]["tpuScaleOut"]["layer"] == "L3"
        client.delete("tpunet.dev/v1alpha1", "NetworkClusterPolicy", "p1")
        with pytest.raises(kerr.NotFoundError):
            client.get("tpunet.dev/v1alpha1", "NetworkClusterPolicy", "p1")

    def test_already_exists_maps_to_409_reason(self, client):
        client.create(make_policy("dup"))
        with pytest.raises(kerr.AlreadyExistsError):
            client.create(make_policy("dup"))

    def test_conflict_maps_to_conflict_error(self, client):
        client.create(make_policy("c1"))
        got = client.get("tpunet.dev/v1alpha1", "NetworkClusterPolicy", "c1")
        stale = json.loads(json.dumps(got))
        got["spec"]["logLevel"] = 3
        client.update(got)
        stale["spec"]["logLevel"] = 5
        with pytest.raises(kerr.ConflictError):
            client.update(stale)   # resourceVersion behind

    def test_list_with_label_selector(self, client):
        lease = {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": "l1", "namespace": "ns1",
                         "labels": {"app": "tpunet-agent"}},
            "spec": {"holderIdentity": "node-1"},
        }
        client.create(lease)
        other = json.loads(json.dumps(lease))
        other["metadata"] = {"name": "l2", "namespace": "ns1",
                             "labels": {"app": "other"}}
        client.create(other)
        items = client.list(
            "coordination.k8s.io/v1", "Lease", namespace="ns1",
            label_selector={"app": "tpunet-agent"},
        )
        assert [o["metadata"]["name"] for o in items] == ["l1"]

    def test_update_status_subresource(self, client):
        client.create(make_policy("st"))
        obj = client.get("tpunet.dev/v1alpha1", "NetworkClusterPolicy", "st")
        obj["status"] = {"state": "All good"}
        client.update_status(obj)
        got = client.get("tpunet.dev/v1alpha1", "NetworkClusterPolicy", "st")
        assert got["status"]["state"] == "All good"

    def test_server_side_apply_create_then_merge(self, client):
        lease = {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": "ap", "namespace": "ns1",
                         "annotations": {"a": "1"}},
            "spec": {"holderIdentity": "n1"},
        }
        created = client.apply(lease)
        assert created["spec"]["holderIdentity"] == "n1"
        patch = {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": "ap", "namespace": "ns1",
                         "annotations": {"b": "2"}},
        }
        merged = client.apply(patch)
        assert merged["metadata"]["annotations"] == {"a": "1", "b": "2"}
        assert merged["spec"]["holderIdentity"] == "n1"   # untouched

    def test_is_openshift_detection(self):
        with WireApiServer(openshift=True) as s:
            assert is_openshift(ApiClient(s.url)) is True
        with WireApiServer(openshift=False) as s:
            assert is_openshift(ApiClient(s.url)) is False


class TestWatchOverWire:
    def _collect(self, watch, n, timeout=10.0, until_name=None):
        """Collect up to ``n`` events, returning early when ``until_name``
        is seen (drop/reconnect tests race benign extra events)."""
        out = []
        deadline = time.time() + timeout
        while len(out) < n and time.time() < deadline:
            ev = watch.next(timeout=0.2)
            if ev:
                out.append(ev)
                if until_name and ev[1]["metadata"]["name"] == until_name:
                    break
        return out

    def test_chunked_watch_stream(self, srv, client):
        w = client.watch("tpunet.dev/v1alpha1", "NetworkClusterPolicy")
        time.sleep(0.3)   # let the stream connect
        srv.cluster.create(make_policy("w1"))
        srv.cluster.create(make_policy("w2"))
        evs = self._collect(w, 2)
        assert [(t, o["metadata"]["name"]) for t, o in evs] == [
            ("ADDED", "w1"), ("ADDED", "w2"),
        ]
        w.stop()

    def test_watch_survives_connection_drop(self, srv, client):
        w = client.watch("tpunet.dev/v1alpha1", "NetworkClusterPolicy")
        time.sleep(0.3)
        srv.cluster.create(make_policy("d1"))
        assert self._collect(w, 1)
        srv.drop_watch_once()
        srv.cluster.create(make_policy("d2"))   # may race the drop
        time.sleep(1.5)                          # reconnect backoff is 1s
        srv.cluster.create(make_policy("d3"))
        # d3 postdates the reconnect: seeing it proves the stream revived
        evs = self._collect(w, 2, timeout=10, until_name="d3")
        assert any(o["metadata"]["name"] == "d3" for _, o in evs)
        w.stop()

    def _raw_events(self, srv, rv, n, timeout=5.0):
        """Open ?watch&resourceVersion=rv raw (no reconnect logic) and
        decode up to n events."""
        import json as json_mod
        import urllib.request

        url = (
            f"{srv.url}/apis/tpunet.dev/v1alpha1/networkclusterpolicies"
            f"?watch=true&resourceVersion={rv}"
        )
        out = []
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            for line in resp:
                if line.strip():
                    out.append(json_mod.loads(line))
                if len(out) >= n:
                    break
        return out

    def test_watch_resume_replays_events_after_rv(self, srv, client):
        """A watch opened from an old resourceVersion replays retained
        history newer than it before going live — the property that
        makes the client's reconnect-with-last-rv lossless for events
        landing in the gap."""
        r1 = srv.cluster.create(make_policy("r1"))
        srv.cluster.create(make_policy("r2"))
        srv.cluster.delete(
            "tpunet.dev/v1alpha1", "NetworkClusterPolicy", "r2"
        )
        evs = self._raw_events(
            srv, r1["metadata"]["resourceVersion"], 2
        )
        assert [(e["type"], e["object"]["metadata"]["name"]) for e in evs] \
            == [("ADDED", "r2"), ("DELETED", "r2")]

    def test_watch_resume_past_retention_gets_genuine_410(self, srv, client):
        """Not fault injection: resuming from a resourceVersion whose
        successor events were compacted out of the history window gets
        the real Expired ERROR event."""
        srv.cluster.HISTORY_LIMIT = 4
        c0 = srv.cluster.create(make_policy("c0"))
        for i in range(8):                   # evict c0's successors
            srv.cluster.create(make_policy(f"c{i + 1}"))
        evs = self._raw_events(
            srv, c0["metadata"]["resourceVersion"], 1
        )
        assert evs[0]["type"] == "ERROR"
        status = evs[0]["object"]
        assert status["code"] == 410 and status["reason"] == "Expired"
        assert "injected" not in status["message"]

    def test_watch_410_gone_ends_stream_for_consumer_relist(
        self, srv, client
    ):
        """410 Expired on resume ENDS the stream (w.stopped) instead of
        silently resuming "from now": continuity is unprovable, and the
        gap's events — deletions included — can only be recovered by
        the consumer's relist (the informer's watch-restart machinery).
        The old transparent resume looked alive while permanently
        missing whatever the compaction window swallowed."""
        w = client.watch("tpunet.dev/v1alpha1", "NetworkClusterPolicy")
        time.sleep(0.3)
        srv.cluster.create(make_policy("g1"))
        assert self._collect(w, 1)   # client now has a resourceVersion
        srv.inject_gone_once()       # next reconnect with rv gets ERROR 410
        srv.drop_watch_once()        # force that reconnect
        deadline = time.time() + 10
        while time.time() < deadline and not w.stopped:
            time.sleep(0.05)
        assert w.stopped, "410 must end the stream, not resume silently"
        # mutations in the gap; a FRESH stream + relist recover them —
        # exactly what Informer._restart_watch does on a dead stream
        srv.cluster.create(make_policy("g2"))
        w2 = client.watch("tpunet.dev/v1alpha1", "NetworkClusterPolicy")
        time.sleep(0.3)
        names = {
            o["metadata"]["name"]
            for o in client.list(
                "tpunet.dev/v1alpha1", "NetworkClusterPolicy"
            )
        }
        assert names == {"g1", "g2"}
        srv.cluster.create(make_policy("g3"))   # live events flow again
        evs = self._collect(w2, 5, timeout=10, until_name="g3")
        assert any(o["metadata"]["name"] == "g3" for _, o in evs)
        w2.stop()


class TestAuthAndTls:
    def test_bearer_token_required(self):
        with WireApiServer(require_token=True) as s:
            s.valid_tokens.add("sekret")
            ok = ApiClient(s.url, token="sekret")
            ok.create(make_policy("t1"))
            bad = ApiClient(s.url, token="wrong")
            with pytest.raises(kerr.ApiError):
                bad.create(make_policy("t2"))

    def test_token_review_endpoint(self):
        with WireApiServer() as s:
            s.valid_tokens.add("good-token")
            c = ApiClient(s.url)
            r = c.create({
                "apiVersion": "authentication.k8s.io/v1",
                "kind": "TokenReview",
                "metadata": {"name": ""},
                "spec": {"token": "good-token"},
            })
            assert r["status"]["authenticated"] is True
            r = c.create({
                "apiVersion": "authentication.k8s.io/v1",
                "kind": "TokenReview",
                "metadata": {"name": ""},
                "spec": {"token": "nope"},
            })
            assert r["status"]["authenticated"] is False

    def test_tls_handshake(self, tmp_path):
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", str(tmp_path / "tls.key"),
             "-out", str(tmp_path / "tls.crt"),
             "-days", "1", "-subj", "/CN=127.0.0.1",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True,
        )
        with WireApiServer(tls_cert_dir=str(tmp_path)) as s:
            assert s.url.startswith("https://")
            c = ApiClient(s.url, ca_file=str(tmp_path / "tls.crt"))
            c.create(make_policy("tls1"))
            assert c.get(
                "tpunet.dev/v1alpha1", "NetworkClusterPolicy", "tls1"
            )["metadata"]["name"] == "tls1"


class TestReconcilerOverWire:
    def test_full_reconcile_through_real_http(self, srv):
        """The envtest-shaped test: real reconciler + real client + real
        HTTP apiserver — CR in, DaemonSet projected, status written."""
        from tpu_network_operator.controller.reconciler import (
            NetworkClusterPolicyReconciler,
        )

        client = ApiClient(srv.url)
        rec = NetworkClusterPolicyReconciler(client, namespace="tpunet-system")
        rec.setup()
        client.create(make_policy("wire-policy", layer="L3"))
        rec.reconcile("wire-policy")
        ds = client.list("apps/v1", "DaemonSet", namespace="tpunet-system")
        assert len(ds) == 1
        args = ds[0]["spec"]["template"]["spec"]["containers"][0]["args"]
        assert "--backend=tpu" in args and "--wait=90s" in args
        rec.reconcile("wire-policy")
        got = client.get(
            "tpunet.dev/v1alpha1", "NetworkClusterPolicy", "wire-policy"
        )
        assert got["status"]["state"] == "No targets"


class TestFromKubeconfig:
    """ApiClient.from_kubeconfig (clientcmd analog) — exercised locally
    against the wire server with a synthetic kubeconfig (the cluster
    tier uses it against real kind clusters, but that tier skips
    without binaries; the parsing/auth wiring must not depend on it)."""

    def _kubeconfig(self, tmp_path, server, token="", cluster_extras=None):
        import yaml as _yaml

        doc = {
            "apiVersion": "v1",
            "kind": "Config",
            "current-context": "test",
            "contexts": [
                {"name": "test",
                 "context": {"cluster": "c1", "user": "u1"}}
            ],
            "clusters": [{"name": "c1", "cluster": {
                "server": server, **(cluster_extras or {}),
            }}],
            "users": [
                {"name": "u1", "user": {"token": token} if token else {}}
            ],
        }
        p = tmp_path / "kubeconfig"
        p.write_text(_yaml.safe_dump(doc))
        return str(p)

    def test_token_auth_round_trip(self, tmp_path):
        from tpu_network_operator.kube.client import ApiClient
        from tpu_network_operator.kube.wire import WireApiServer

        srv = WireApiServer(require_token=True)
        srv.valid_tokens.add("sekrit")
        srv.start()
        try:
            kc = self._kubeconfig(tmp_path, srv.url, token="sekrit")
            c = ApiClient.from_kubeconfig(kc)
            c.create({
                "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": "kc-lease", "namespace": "default"},
                "spec": {"holderIdentity": "n1"},
            })
            got = c.get("coordination.k8s.io/v1", "Lease", "kc-lease",
                        "default")
            assert got["spec"]["holderIdentity"] == "n1"
        finally:
            srv.stop()

    def test_unknown_context_is_typed_error(self, tmp_path):
        import pytest as _pytest

        from tpu_network_operator.kube import errors as kerr
        from tpu_network_operator.kube.client import ApiClient

        kc = self._kubeconfig(tmp_path, "http://127.0.0.1:1")
        with _pytest.raises(kerr.ApiError, match="context"):
            ApiClient.from_kubeconfig(kc, context="nope")

    def test_inline_cert_data_materializes_0600_files(
        self, tmp_path, monkeypatch
    ):
        import base64
        import glob
        import os
        import stat
        import tempfile

        from tpu_network_operator.kube.client import ApiClient

        # isolate materialized files in a per-test tempdir so the
        # assertions cannot hit (or be satisfied by) unrelated pems
        monkeypatch.setattr(tempfile, "tempdir", str(tmp_path))
        # arbitrary bytes suffice: with insecure-skip-tls-verify the
        # constructor takes the unverified-context branch and the CA
        # content is materialized but not parsed (client certs, which
        # DO get parsed via load_cert_chain, need real key material —
        # the kind leg of the cluster tier covers that path)
        pem = base64.b64encode(b"-----BEGIN CERTIFICATE-----\n"
                               b"MIIB\n-----END CERTIFICATE-----\n")
        kc = self._kubeconfig(
            tmp_path, "https://127.0.0.1:1", cluster_extras={
                "insecure-skip-tls-verify": True,
                "certificate-authority-data": pem.decode(),
            },
        )
        ApiClient.from_kubeconfig(kc)
        pems = glob.glob(os.path.join(str(tmp_path), "*.pem"))
        assert len(pems) == 1, pems
        mode = stat.S_IMODE(os.stat(pems[0]).st_mode)
        assert mode == 0o600, oct(mode)


class TestConcurrentApply:
    def test_concurrent_ssa_create_has_one_winner(self):
        """The 201-vs-200 decision is atomic in the store: N threads
        SSA-applying the same missing object must observe exactly ONE
        201 Created (the real apiserver's behavior under the same
        race)."""
        import json as _json
        import threading
        import urllib.request

        from tpu_network_operator.kube.wire import WireApiServer

        srv = WireApiServer().start()
        try:
            path = (f"{srv.url}/apis/coordination.k8s.io/v1/namespaces/"
                    "default/leases/race?fieldManager=t&force=true")
            codes = []
            lock = threading.Lock()

            def apply_once(i):
                body = _json.dumps({
                    "apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease",
                    "metadata": {"name": "race", "namespace": "default"},
                    "spec": {"holderIdentity": f"w{i}"},
                }).encode()
                req = urllib.request.Request(
                    path, data=body, method="PATCH",
                    headers={"Content-Type":
                             "application/apply-patch+yaml"},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    with lock:
                        codes.append(resp.status)

            threads = [
                threading.Thread(target=apply_once, args=(i,))
                for i in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sorted(codes) == [200] * 7 + [201], codes
        finally:
            srv.stop()
