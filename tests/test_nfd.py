"""NFD label file tests (ref cmd/discover/main.go:240-246 behavior)."""


from tpu_network_operator.nfd import (
    TPU_READY_LABEL,
    remove_readiness_label,
    write_readiness_label,
)


def test_write_when_nfd_present(tmp_path):
    d = tmp_path / "etc/kubernetes/node-feature-discovery/features.d"
    d.mkdir(parents=True)
    assert write_readiness_label(TPU_READY_LABEL, root=str(tmp_path))
    content = (d / "scale-out-readiness.txt").read_text()
    # must live under the feature.node.kubernetes.io vendor namespace or
    # NFD's default deny-label-ns silently drops it
    assert content == "tpunet.feature.node.kubernetes.io/tpu-scale-out=true\n"


def test_skip_when_nfd_absent(tmp_path):
    assert not write_readiness_label(TPU_READY_LABEL, root=str(tmp_path))
    assert list(tmp_path.rglob("*")) == []


def test_remove_idempotent(tmp_path):
    d = tmp_path / "etc/kubernetes/node-feature-discovery/features.d"
    d.mkdir(parents=True)
    write_readiness_label(TPU_READY_LABEL, root=str(tmp_path))
    remove_readiness_label(root=str(tmp_path))
    assert not (d / "scale-out-readiness.txt").exists()
    remove_readiness_label(root=str(tmp_path))  # second time: no error
